"""Policy subsystem units (ISSUE 16): priority tiers + annotation
threading, the shared strategy registry and its error shape, ordering
strategies (priority / DRF) against the real extender, the vectorized
preemption search (ONE batched pass), and the defragmenter."""

import pytest

from spark_scheduler_tpu.models.reservations import (
    PRIORITY_CLASS_ANNOTATION,
    new_resource_reservation,
)
from spark_scheduler_tpu.models.resources import Resources
from spark_scheduler_tpu.policy import (
    PRIORITY_CLASSES,
    UnknownStrategyError,
    effective_priority,
    pod_priority,
    resolve,
)
from spark_scheduler_tpu.policy.priority import parse_priority_class
from spark_scheduler_tpu.testing.harness import (
    Harness,
    new_node,
    overcommit_violations,
    static_allocation_spark_pods,
)


class ManualClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _policy_harness(clock=None, **kw):
    kw.setdefault("policy_enabled", True)
    kw.setdefault("resync_gap_seconds", 1e12)
    return Harness(clock=clock, **kw)


def _stamped(app_id, execs, pclass, clock, instance_group=None):
    kw = {} if instance_group is None else {"instance_group": instance_group}
    pods = static_allocation_spark_pods(app_id, execs, **kw)
    if pclass is not None:
        pods[0].annotations[PRIORITY_CLASS_ANNOTATION] = pclass
    for p in pods:
        p.creation_timestamp = clock()
    return pods


def _admit(h, pods, names):
    r = h.schedule(pods[0], names)
    if r.ok:
        for p in pods[1:]:
            assert h.schedule(p, names).ok
    return r


# -------------------------------------------------------------- registry


def test_select_binpacker_unknown_name_lists_valid_names():
    from spark_scheduler_tpu.core.binpacker import (
        BINPACK_FUNCTIONS,
        select_binpacker,
    )

    with pytest.raises(UnknownStrategyError) as exc:
        select_binpacker("nope")
    assert isinstance(exc.value, ValueError)  # back-compat except clauses
    for name in BINPACK_FUNCTIONS:
        assert name in str(exc.value)
    assert exc.value.name == "nope"
    assert exc.value.valid == sorted(BINPACK_FUNCTIONS)


def test_policy_ordering_unknown_name_same_error_shape():
    from spark_scheduler_tpu.policy.engine import PolicyConfig, PolicyEngine

    with pytest.raises(UnknownStrategyError) as exc:
        PolicyEngine(
            PolicyConfig(ordering="wrongo"),
            backend=None,
            rr_cache=None,
            pod_lister=None,
            soft_store=None,
            reservation_manager=None,
            solver=None,
            clock=lambda: 0.0,
        )
    assert "fifo" in str(exc.value) and "drf" in str(exc.value)


def test_resolve_passes_through_known_names():
    assert resolve("a", {"a": 1, "b": 2}, "thing") == 1


# -------------------------------------------------------------- priority


def test_parse_priority_class():
    assert parse_priority_class(None) == PRIORITY_CLASSES["default"]
    assert parse_priority_class("system") == 300
    assert parse_priority_class("HIGH") == 200
    assert parse_priority_class("250") == 250
    assert parse_priority_class("junk") == PRIORITY_CLASSES["default"]


def test_effective_priority_promotes_and_caps():
    assert effective_priority(0, 0.0, 300.0) == 0
    assert effective_priority(0, 299.0, 300.0) == 0
    assert effective_priority(0, 300.0, 300.0) == 100
    assert effective_priority(0, 900.0, 300.0) == 200  # capped at "high"
    assert effective_priority(200, 10_000.0, 300.0) == 200
    assert effective_priority(300, 10_000.0, 300.0) == 300  # never demoted
    assert effective_priority(0, 10_000.0, 0.0) == 0  # promotion disabled


def test_priority_annotation_stamped_onto_reservation():
    clk = ManualClock()
    h = _policy_harness(clock=clk)
    h.add_nodes(new_node("n1"))
    pods = _stamped("app-pc", 1, "high", clk)
    assert _admit(h, pods, ["n1"]).ok
    rr = h.get_reservation("namespace", "app-pc")
    assert rr.annotations[PRIORITY_CLASS_ANNOTATION] == "high"
    # Absent class -> no annotation (the default path stays untouched).
    pods2 = _stamped("app-none", 1, None, clk)
    assert _admit(h, pods2, ["n1"]).ok
    rr2 = h.get_reservation("namespace", "app-none")
    assert PRIORITY_CLASS_ANNOTATION not in rr2.annotations
    assert pod_priority(pods[0]) == 200
    assert pod_priority(pods2[0]) == PRIORITY_CLASSES["default"]


def test_priority_class_rides_v1beta2_wire_losslessly():
    from spark_scheduler_tpu.server.conversion import (
        rr_v1beta2_from_wire,
        rr_v1beta2_to_wire,
    )

    one = Resources.from_quantities("1", "1Gi", "0", round_up=False)
    driver = static_allocation_spark_pods("app-w", 1)[0]
    driver.annotations[PRIORITY_CLASS_ANNOTATION] = "system"
    rr = new_resource_reservation("n0", ["n0"], driver, one, one)
    wire = rr_v1beta2_to_wire(rr)
    # First-class spec field on the wire; the carrier annotation is
    # stripped from wire metadata (single source of truth).
    assert wire["spec"]["priorityClass"] == "system"
    assert PRIORITY_CLASS_ANNOTATION not in wire["metadata"].get(
        "annotations", {}
    )
    back = rr_v1beta2_from_wire(wire)
    assert back.annotations[PRIORITY_CLASS_ANNOTATION] == "system"

    # Absent class -> wire byte-identical to the pre-policy shape.
    driver2 = static_allocation_spark_pods("app-w2", 1)[0]
    rr2 = new_resource_reservation("n0", ["n0"], driver2, one, one)
    wire2 = rr_v1beta2_to_wire(rr2)
    assert "priorityClass" not in wire2["spec"]
    back2 = rr_v1beta2_from_wire(wire2)
    assert PRIORITY_CLASS_ANNOTATION not in back2.annotations


def test_crd_v1beta2_schema_accepts_priority_class():
    from spark_scheduler_tpu.models.crds import resource_reservation_crd

    crd = resource_reservation_crd()
    v2 = next(
        v for v in crd["spec"]["versions"] if v["name"] == "v1beta2"
    )
    spec_props = v2["schema"]["openAPIV3Schema"]["properties"]["spec"][
        "properties"
    ]
    assert spec_props["priorityClass"] == {"type": "string"}


# -------------------------------------------------------- priority ordering


def _free_app(h, app_id):
    rr = h.get_reservation("namespace", app_id)
    for pod in h.app.pod_lister.list_app_pods(app_id, "namespace"):
        h.delete_pod(pod)
    if rr is not None:
        h.app.rr_cache.delete(rr.namespace, rr.name)


def test_priority_ordering_blocks_low_behind_high_then_promotes():
    clk = ManualClock()
    h = _policy_harness(
        clock=clk,
        policy_ordering="priority",
        policy_promote_after_s=100.0,
    )
    h.add_nodes(new_node("n1"))
    names = ["n1"]
    # Fill the node so both gangs pend: 8 cpu = filler-a (4) + filler-b (3).
    fill_a = _stamped("fill-a", 3, "system", clk)
    fill_b = _stamped("fill-b", 2, "system", clk)
    assert _admit(h, fill_a, names).ok
    assert _admit(h, fill_b, names).ok
    low = _stamped("low-a", 2, "low", clk)  # 3 cpu
    clk.advance(1.0)
    high = _stamped("high-a", 2, "high", clk)  # 3 cpu, YOUNGER
    assert not h.schedule(low[0], names).ok
    assert not h.schedule(high[0], names).ok
    # Free 3 cpu — room for exactly one of the two pending gangs. The
    # batched FIFO prefix puts the younger-but-higher gang ahead of the
    # older low gang, so low stays denied and high admits first.
    _free_app(h, "fill-b")
    assert not h.schedule(low[0], names).ok
    assert h.schedule(high[0], names).ok
    # Age-promotion: once the low gang ages to the cap it stops being
    # blocked by fresh high arrivals (equal tier, FIFO tiebreak on age).
    _free_app(h, "fill-a")  # 5 cpu free now; low (3) + blocker would be 6
    clk.advance(250.0)  # low effective: 0 + 2*100 = 200 = "high"
    fresh_high = _stamped("high-b", 2, "high", clk)
    h.add_pods(fresh_high[0])
    assert h.schedule(low[0], names).ok
    assert overcommit_violations(h.app, h.backend) == []


# ------------------------------------------------------------ DRF ordering


def test_drf_hard_blocks_richer_group_and_admits_poorer():
    clk = ManualClock()
    h = _policy_harness(clock=clk, policy_ordering="drf")
    h.add_nodes(
        new_node("ga-1", instance_group="group-a"),
        new_node("gb-1", instance_group="group-b"),
    )
    # Group A becomes the dominant-share group.
    a1 = _stamped("a-1", 4, None, clk, instance_group="group-a")
    assert _admit(h, a1, ["ga-1"]).ok
    shares = h.app.extender._policy.shares
    assert shares.dominant_share("group-a") > shares.dominant_share("group-b")
    # A pending group-b gang with the smaller share hard-blocks group a...
    clk.advance(10.0)
    b1 = _stamped("b-1", 1, None, clk, instance_group="group-b")
    h.add_pods(b1[0])
    a2 = _stamped("a-2", 1, None, clk, instance_group="group-a")
    r = h.schedule(a2[0], ["ga-1"])
    assert not r.ok and r.outcome == "failure-earlier-driver"
    # ...while group b itself admits (smallest dominant share first).
    assert _admit(h, b1, ["gb-1"]).ok
    # With b's gang admitted (b pending queue empty), group a proceeds.
    r2 = h.schedule(a2[0], ["ga-1"])
    assert r2.ok
    assert overcommit_violations(h.app, h.backend) == []


def test_group_usage_aggregates_match_rebuild_oracle():
    clk = ManualClock()
    h = _policy_harness(clock=clk, policy_ordering="drf")
    h.add_nodes(
        new_node("ga-1", instance_group="group-a"),
        new_node("gb-1", instance_group="group-b"),
    )
    a1 = _stamped("a-or-1", 2, None, clk, instance_group="group-a")
    b1 = _stamped("b-or-1", 3, None, clk, instance_group="group-b")
    assert _admit(h, a1, ["ga-1"]).ok
    assert _admit(h, b1, ["gb-1"]).ok
    # Teardown one app: delta-maintained totals must track the delete.
    for p in a1:
        h.delete_pod(p)
    rr = h.get_reservation("namespace", "a-or-1")
    h.app.rr_cache.delete(rr.namespace, rr.name)
    shares = h.app.extender._policy.shares
    live = {g: u for g, u in shares.snapshot().items() if any(u)}
    shares.rebuild()  # from-scratch oracle
    oracle = {g: u for g, u in shares.snapshot().items() if any(u)}
    assert live == oracle and "group-b" in oracle


# -------------------------------------------------------------- preemption


def _fill_then_preempt(clk, **kw):
    h = _policy_harness(
        clock=clk, policy_ordering="priority", policy_preemption=True, **kw
    )
    h.add_nodes(new_node("n1"), new_node("n2"))
    names = ["n1", "n2"]
    for i, pclass in enumerate(["low", "low", "default"]):
        pods = _stamped(f"bg-{i}", 3, pclass, clk)
        assert _admit(h, pods, names).ok  # 3 gangs x 4cpu = 12 of 16
    filler = _stamped("bg-fill", 2, "default", clk)
    assert _admit(h, filler, names).ok  # 15 of 16 cpu
    return h, names


def test_preemption_solo_is_one_batched_pass_minimal_set():
    clk = ManualClock()
    h, names = _fill_then_preempt(clk)
    solver = h.app.solver
    calls = []
    orig = solver.preemption_search

    def counting(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    solver.preemption_search = counting
    from spark_scheduler_tpu.metrics.registry import MetricRegistry
    from spark_scheduler_tpu.policy.engine import (
        PREEMPTION_EVICTIONS,
        PREEMPTIONS,
    )

    reg = MetricRegistry()
    h.app.extender._policy._metrics = reg
    high = _stamped("hi", 4, "high", clk)  # needs 5 cpu; 1 free
    r = h.schedule(high[0], names)
    assert r.ok
    assert reg.counter(PREEMPTIONS).value == 1
    assert reg.counter(PREEMPTION_EVICTIONS).value == 1
    # ONE batched masked-fit pass over ALL candidate sets — never a
    # per-candidate kernel loop (the acceptance criterion).
    assert len(calls) == 1
    recs = [
        rec
        for rec in h.app.recorder.query(limit=100)
        if rec.get("preemption")
    ]
    assert len(recs) == 1
    pre = recs[0]["preemption"]
    # 3 evictable victims enumerated (system/high none; "default" filler +
    # 2 lows + 1 default), minimal prefix chosen: cheapest-first order is
    # (low, low, default...) and one 4-cpu low gang suffices for 5 cpu.
    assert pre["candidates"] >= 2
    assert len(pre["evicted"]) == 1
    evicted = pre["evicted"][0]
    assert evicted.startswith("bg-")
    assert h.get_reservation("namespace", evicted) is None
    assert h.get_reservation("namespace", "hi") is not None
    assert overcommit_violations(h.app, h.backend) == []


def test_preemption_never_touches_protected_class():
    clk = ManualClock()
    h = _policy_harness(
        clock=clk, policy_ordering="priority", policy_preemption=True
    )
    h.add_nodes(new_node("n1"))
    sys_pods = _stamped("sys-1", 5, "system", clk)
    assert _admit(h, sys_pods, ["n1"]).ok  # 6 of 8 cpu
    high = _stamped("hi-p", 3, "high", clk)
    r = h.schedule(high[0], ["n1"])
    # Nothing below "high" is running -> no eviction set -> plain denial.
    assert not r.ok and r.outcome == "failure-fit"
    assert h.get_reservation("namespace", "sys-1") is not None


def test_preemption_respects_age_promoted_victims():
    clk = ManualClock()
    h = _policy_harness(
        clock=clk,
        policy_ordering="priority",
        policy_preemption=True,
        policy_promote_after_s=100.0,
    )
    h.add_nodes(new_node("n1"))
    low = _stamped("low-old", 5, "low", clk)
    assert _admit(h, low, ["n1"]).ok
    clk.advance(500.0)  # low promoted to the cap ("high")
    high = _stamped("hi-late", 3, "high", clk)
    r = h.schedule(high[0], ["n1"])
    assert not r.ok  # equal effective tier is not evictable
    assert h.get_reservation("namespace", "low-old") is not None


def test_preemption_windowed_denies_then_retry_admits():
    clk = ManualClock()
    h, names = _fill_then_preempt(clk)
    from spark_scheduler_tpu.core.extender import ExtenderArgs

    high = _stamped("hi-w", 4, "high", clk)
    h.add_pods(high[0])
    t = h.app.extender.predicate_window_dispatch(
        [ExtenderArgs(pod=high[0], node_names=names)]
    )
    (res,) = h.app.extender.predicate_window_complete(t)
    # Windowed semantics: evict but deny THIS round...
    assert not res.ok
    assert "preempted" in list(res.failed_nodes.values())[0]
    # ...and the pod's retry admits against the freed cluster.
    r2 = h.schedule(high[0], names)
    assert r2.ok
    assert overcommit_violations(h.app, h.backend) == []


def test_preemption_freed_prefixes_are_monotone_cumulative():
    clk = ManualClock()
    h, names = _fill_then_preempt(clk)
    eng = h.app.extender._policy
    victims = eng.preemption.enumerate_victims(200, None)
    assert len(victims) == 4  # two lows + two defaults; cheapest first
    assert [v[0] for v in victims] == sorted(v[0] for v in victims)
    freed = eng.preemption.freed_prefixes(victims, h.app.solver.registry)
    assert freed.shape[0] == 4
    totals = freed.sum(axis=(1, 2))
    assert all(totals[i] < totals[i + 1] for i in range(3))


# ------------------------------------------------------------- defrag


def _fragmented_cluster(clk):
    """3 nodes, each 7/8 cpu+mem reserved (6 hard + 1 soft extra): free
    1cpu/1Gi per node = zero 2cpu/2Gi slots everywhere, while releasing a
    soft extra completes one slot on its node."""
    from spark_scheduler_tpu.testing.harness import (
        dynamic_allocation_spark_pods,
    )

    h = _policy_harness(clock=clk)
    for i in range(3):
        h.add_nodes(new_node(f"fn{i}"))
    for i in range(3):
        pods = dynamic_allocation_spark_pods(f"frag-{i}", 5, 6)
        for p in pods:
            p.creation_timestamp = clk()
        assert _admit(h, pods, [f"fn{i}"]).ok
    soft = h.soft_reservations()
    assert sum(len(sr.reservations) for sr in soft.values()) == 3
    return h


def test_defragmenter_reduces_fragmentation_within_budget():
    from spark_scheduler_tpu.policy.defrag import Defragmenter

    clk = ManualClock()
    h = _fragmented_cluster(clk)
    d = Defragmenter(
        h.backend,
        h.app.soft_store,
        h.app.reservation_manager,
        clk,
        budget=2,
        unit=Resources.from_quantities("2", "2Gi", "0", round_up=False),
    )
    before = d.fragmentation()
    assert before == 1.0  # every free byte stranded at slot granularity
    out = d.run_once(force=True)
    assert out["migrations"] == 2  # budget bounds pods disturbed per pass
    assert out["fragmentation_after"] < out["fragmentation_before"]
    # Hard reservations untouched: every gang keeps driver + min slots.
    for i in range(3):
        rr = h.get_reservation("namespace", f"frag-{i}")
        assert rr is not None and len(rr.spec.reservations) >= 6
    assert overcommit_violations(h.app, h.backend) == []
    out2 = d.run_once(force=True)  # drain the remaining stranded node
    assert out2["fragmentation_after"] == 0.0
    assert d.migrations == 3


def test_engine_defrag_interval_gating_and_metrics():
    from spark_scheduler_tpu.metrics.registry import MetricRegistry
    from spark_scheduler_tpu.policy.defrag import FRAGMENTATION_GAUGE

    clk = ManualClock()
    reg = MetricRegistry()
    h = _policy_harness(
        clock=clk,
        policy_defrag=True,
        policy_defrag_interval_s=30.0,
        metrics=None,
    )
    h.add_nodes(new_node("n1"))
    eng = h.app.extender._policy
    eng._metrics = reg
    eng.defrag._metrics = reg
    assert eng.maybe_defrag() is not None
    assert eng.maybe_defrag() is None  # inside the interval
    clk.advance(31.0)
    assert eng.maybe_defrag() is not None
    assert reg.gauge(FRAGMENTATION_GAUGE).value == 0.0  # empty cluster
